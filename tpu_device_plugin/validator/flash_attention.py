"""Pallas flash-attention kernel — the burn-in's hot op, TPU-first.

Causal multi-head attention computed blockwise with the online-softmax
recurrence so the (S, S) score matrix never materializes in HBM: each grid
step streams one (block_q, block_k) tile through VMEM, keeping running max
`m`, normalizer `l`, and output accumulator in VMEM scratch. The MXU sees two
matmuls per tile (Q·Kᵀ and P·V) with float32 accumulation; blocks entirely
above the causal diagonal are skipped via `pl.when`.

Training integration uses `jax.custom_vjp` with Pallas backward kernels in
the FlashAttention-2 shape: the forward additionally stores the per-row
logsumexp (replicated across 128 lanes, the same layout the public JAX TPU
kernel uses for its `l`/`m` residuals), and the backward recomputes P per
tile from (q, k, lse) — two grid passes, one accumulating (dk, dv) per key
block and one accumulating dq per query block. Memory stays O(S) in the
backward exactly like the forward; the (S, S) matrix never exists in HBM in
either direction.

`interpret=True` runs the same kernels on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _reference_attention(q, k, v, sm_scale: float, causal: bool):
    """Plain einsum attention; used for the custom-vjp backward and tests.

    Shapes: q, k, v are (heads_batch, seq, head_dim).
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), jnp.bool_))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


LANES = 128  # TPU lane count; row-vector residuals are replicated across it


def _bcast_rows(x, ncols: int):
    """(rows, 128) lane-replicated vector -> (rows, ncols) broadcast."""
    if ncols <= LANES:
        return x[:, :ncols]
    if ncols % LANES:
        raise ValueError(f"block size {ncols} not a multiple of {LANES}")
    return jnp.tile(x, (1, ncols // LANES))


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                  sm_scale: float, causal: bool, save_lse: bool,
                  block_q: int, block_k: int, num_k: int, seq_len: int):
    if save_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        lse_ref, (m_ref, l_ref, acc_ref) = None, rest
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip tiles strictly above the causal diagonal
    run = (kj * block_k <= qi * block_q + block_q - 1) if causal else (kj >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                       # (block_q, d)
        k = k_ref[0]                       # (block_k, d)
        v = v_ref[0]
        # Padding discipline: when seq_len is not a block multiple, Pallas
        # pads the trailing block with undefined data (NaN in interpret
        # mode). Padding key columns must be masked out of the softmax and
        # padding value rows zeroed, or NaN poisons every query row.
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        k_valid = cols < seq_len
        v_rows_valid = (kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 0)) < seq_len
        v = jnp.where(v_rows_valid, v, jnp.zeros_like(v))
        k = jnp.where(v_rows_valid, k, jnp.zeros_like(k))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        mask = k_valid if not causal else (k_valid & (cols <= rows))
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]                                # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                               # (bq, bk) f32
        alpha = jnp.exp(m_prev - m_new)                      # (bq, 1)
        l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    last_k = (jnp.minimum((qi * block_q + block_q - 1) // block_k, num_k - 1)
              if causal else num_k - 1)

    @pl.when(kj == last_k)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)
        if save_lse:
            lse_ref[0] = jnp.broadcast_to(
                m_ref[:, :1] + jnp.log(l_ref[:, :1]), (block_q, LANES))


def _flash_3d(q, k, v, sm_scale: float, causal: bool,
              block_q: int, block_k: int, interpret: bool,
              return_lse: bool = False):
    """(heads_batch, seq, head_dim) flash attention via pallas_call."""
    hb, seq, d = q.shape
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    num_q = pl.cdiv(seq, block_q)
    num_k = pl.cdiv(seq, block_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal, save_lse=return_lse,
        block_q=block_q, block_k=block_k, num_k=num_k, seq_len=seq)
    out_shape = [jax.ShapeDtypeStruct((hb, seq, d), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))]
    if return_lse:
        # logsumexp residual, lane-replicated (same layout as the public JAX
        # TPU flash kernel keeps its l/m residuals)
        out_shape.append(jax.ShapeDtypeStruct((hb, seq, LANES), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)))
    result = pl.pallas_call(
        kernel,
        grid=(hb, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return result if return_lse else result[0]


# --- backward kernels (FlashAttention-2 two-pass shape) ----------------------

def _bwd_tile(q, do, k, v, lse, di, valid, sm_scale):
    """Shared per-tile math: recompute P from lse, return (p, ds) masked."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale       # (bq, bk)
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # (bq, bk)
    # explicit mask: padded-row lse/di are garbage and 0*NaN poisons sums
    ds = jnp.where(valid, p * (dp - di) * sm_scale, 0.0)
    return p, ds


def _masks(qi, kj, block_q, block_k, seq_len, causal, q_shape, k_shape):
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    valid = (rows < seq_len) & (cols < seq_len)
    if causal:
        valid &= cols <= rows
    q_rows_ok = (qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, q_shape, 0)) < seq_len
    k_rows_ok = (kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, k_shape, 0)) < seq_len
    return rows, cols, valid, q_rows_ok, k_rows_ok


def _flash_bwd_dkv_kernel(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          sm_scale: float, causal: bool,
                          block_q: int, block_k: int, num_q: int, seq_len: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: a key block only sees query blocks at or below its diagonal
    run = (qi * block_q + block_q - 1 >= kj * block_k) if causal else (qi >= 0)

    @pl.when(run)
    def _compute():
        q, do, k, v = q_ref[0], do_ref[0], k_ref[0], v_ref[0]
        _, _, valid, q_ok, k_ok = _masks(
            qi, kj, block_q, block_k, seq_len, causal, q.shape, k.shape)
        q = jnp.where(q_ok, q, jnp.zeros_like(q))
        do = jnp.where(q_ok, do, jnp.zeros_like(do))
        k = jnp.where(k_ok, k, jnp.zeros_like(k))
        v = jnp.where(k_ok, v, jnp.zeros_like(v))
        lse = _bcast_rows(lse_ref[0], block_k)
        di = _bcast_rows(di_ref[0], block_k)
        p, ds = _bwd_tile(q, do, k, v, lse, di, valid, sm_scale)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, d)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, do_ref, lse_ref, di_ref, k_ref, v_ref,
                         dq_ref, dq_acc, *,
                         sm_scale: float, causal: bool,
                         block_q: int, block_k: int, num_k: int, seq_len: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (kj * block_k <= qi * block_q + block_q - 1) if causal else (kj >= 0)

    @pl.when(run)
    def _compute():
        q, do, k, v = q_ref[0], do_ref[0], k_ref[0], v_ref[0]
        _, _, valid, q_ok, k_ok = _masks(
            qi, kj, block_q, block_k, seq_len, causal, q.shape, k.shape)
        q = jnp.where(q_ok, q, jnp.zeros_like(q))
        do = jnp.where(q_ok, do, jnp.zeros_like(do))
        k = jnp.where(k_ok, k, jnp.zeros_like(k))
        v = jnp.where(k_ok, v, jnp.zeros_like(v))
        lse = _bcast_rows(lse_ref[0], block_k)
        di = _bcast_rows(di_ref[0], block_k)
        _, ds = _bwd_tile(q, do, k, v, lse, di, valid, sm_scale)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, d)

    last_k = (jnp.minimum((qi * block_q + block_q - 1) // block_k, num_k - 1)
              if causal else num_k - 1)

    @pl.when(kj == last_k)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_3d(q, k, v, o, lse, d_out, sm_scale, causal,
                  block_q, block_k, interpret, out_dtype=None):
    """Pallas backward: dq, dk, dv with O(S) memory (no (S, S) in HBM).

    `out_dtype` overrides the gradient output dtype (the kernels accumulate
    in f32 VMEM scratch regardless; this only controls the final cast).
    Ring flash passes f32 so per-step partials are not rounded to bf16
    before being summed across ring steps."""
    hb, seq, d = q.shape
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    num_q = pl.cdiv(seq, block_q)
    num_k = pl.cdiv(seq, block_k)
    # D_i = rowsum(dO ∘ O), lane-replicated like lse
    di = jnp.broadcast_to(
        jnp.sum(d_out.astype(jnp.float32) * o.astype(jnp.float32),
                axis=-1, keepdims=True),
        (hb, seq, LANES))

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, j, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q=num_q, seq_len=seq),
        grid=(hb, num_k, num_q),
        in_specs=[q_spec, q_spec, row_spec, row_spec, kv_spec, kv_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((hb, seq, d), out_dtype or k.dtype),
                   jax.ShapeDtypeStruct((hb, seq, d), out_dtype or v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, d_out, lse, di, k, v)

    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec2 = pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_k=num_k, seq_len=seq),
        grid=(hb, num_q, num_k),
        in_specs=[q_spec2, q_spec2, row_spec2, row_spec2, kv_spec2, kv_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct((hb, seq, d), out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, d_out, lse, di, k, v)
    return dq, dk, dv


# Backward-block default from the TPU v5 lite hardware sweep
# (docs/validator_tpu_bwd_sweep_r03.json): 256x256 wins at every measured
# seq — full-train speedup vs einsum 0.89->1.56 at 2048 and 1.53->2.89 at
# 4096 relative to inheriting the forward's 128-blocks. Clamped to seq.
DEFAULT_BWD_BLOCK = 256


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def flash_attention(q, k, v, sm_scale: Optional[float] = None,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None):
    """Blockwise causal attention. q, k, v: (heads_batch, seq, head_dim).

    `bwd_block_q`/`bwd_block_k` tile the backward kernels independently of
    the forward (None = the hardware-swept DEFAULT_BWD_BLOCK, clamped to
    seq). The backward touches ~2.5x the operands per tile (FA-2 two-pass:
    dkv then dq), so its MXU-optimal block shape differs from the
    forward's — larger tiles amortize the lse/di reloads across more MXU
    work (sweep: attn_bench --bwd-blocks).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _flash_3d(q, k, v, sm_scale, causal, block_q, block_k, interpret)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
         bwd_block_q, bwd_block_k):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out, lse = _flash_3d(q, k, v, sm_scale, causal, block_q, block_k,
                         interpret, return_lse=True)
    return out, (q, k, v, out, lse)


def _bwd(sm_scale, causal, block_q, block_k, interpret,
         bwd_block_q, bwd_block_k, residuals, d_out):
    q, k, v, o, lse = residuals
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _flash_bwd_3d(q, k, v, o, lse, d_out, sm_scale, causal,
                         bwd_block_q or DEFAULT_BWD_BLOCK,
                         bwd_block_k or DEFAULT_BWD_BLOCK,
                         interpret)


flash_attention.defvjp(_fwd, _bwd)
