"""Guest slice validator: mesh inference, SPMD workload, probe report.

Runs on the virtual CPU mesh (8 devices via xla_force_host_platform_device_count).
"""

import pytest

jax = pytest.importorskip("jax")

from tpu_device_plugin.validator.mesh import infer_mesh_shape, slice_mesh
from tpu_device_plugin.validator.probe import validate_slice
from tpu_device_plugin.validator.workload import ModelConfig, build_workload


def cpus():
    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("need 8 virtual CPU devices")
    return devs


def test_infer_mesh_shape_defaults():
    assert infer_mesh_shape(8) == (2, 1, 4)
    assert infer_mesh_shape(4) == (1, 1, 4)
    assert infer_mesh_shape(1) == (1, 1, 1)
    assert infer_mesh_shape(8, tp=2, sp=2) == (2, 2, 2)
    with pytest.raises(ValueError):
        infer_mesh_shape(6, tp=4)


def test_slice_mesh_axes():
    mesh = slice_mesh(cpus(), tp=2, sp=2)
    assert mesh.axis_names == ("dp", "sp", "tp")
    assert mesh.devices.shape == (2, 2, 2)


SMALL = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                    seq_len=16, batch=4)


def test_single_device_training_step():
    step, params, momentum, tokens = build_workload(SMALL, slice_mesh(cpus()[:1]))
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(3):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_multi_axis_training_step():
    mesh = slice_mesh(cpus(), tp=2, sp=2)
    step, params, momentum, tokens = build_workload(SMALL, mesh)
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(3):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_sharded_matches_single_device():
    """SPMD correctness: dp/sp/tp sharding must not change the math."""
    single_step, p1, m1, t1 = build_workload(SMALL, slice_mesh(cpus()[:1]), seed=7)
    _, _, loss_single = single_step(p1, m1, t1)
    mesh = slice_mesh(cpus(), tp=2, sp=2)
    sharded_step, p8, m8, t8 = build_workload(SMALL, mesh, seed=7)
    _, _, loss_sharded = sharded_step(p8, m8, t8)
    assert abs(float(loss_single) - float(loss_sharded)) < 2e-2


def test_validate_slice_report():
    report = validate_slice(cfg=SMALL, steps=3, tp=2, devices=cpus())
    assert report.ok, report.error
    assert report.n_devices == 8
    assert report.mesh_shape == {"dp": 4, "sp": 1, "tp": 2}
    assert report.loss_end < report.loss_start
    assert report.step_time_s > 0
    assert report.devices_visible_s > 0
    payload = report.to_json()
    assert '"ok": true' in payload


def test_validate_slice_single_device():
    report = validate_slice(cfg=SMALL, steps=2, devices=cpus()[:1])
    assert report.ok, report.error
    assert report.n_devices == 1


def test_graft_dryrun_multichip():
    import __graft_entry__ as graft
    graft.dryrun_multichip(8)


def test_microbench_failure_never_vetoes(monkeypatch):
    """A diagnostic microbench failure must not flip a passing validation."""
    from tpu_device_plugin.validator import probe as probe_mod

    def boom(device):
        raise MemoryError("256MiB scratch OOM")

    monkeypatch.setattr(probe_mod, "_microbench", boom)
    report = probe_mod.validate_slice(cfg=SMALL, steps=2, devices=cpus()[:1])
    assert report.ok is True
    assert report.matmul_tflops == 0.0
    assert "microbench skipped" in report.error


def test_validate_slice_infer_mode():
    """Serving mode: forward-only latency percentiles, finite-logits gate."""
    report = validate_slice(cfg=SMALL, steps=5, tp=2, devices=cpus(),
                            mode="infer")
    assert report.ok, report.error
    assert report.infer_p50_ms > 0
    assert report.infer_p99_ms >= report.infer_p50_ms
    assert report.tokens_per_s > 0
    assert report.loss_start == 0.0  # no training happened
    assert report.mesh_shape == {"dp": 4, "sp": 1, "tp": 2}


def test_infer_matches_workload_forward():
    """build_infer must run the same model as the training forward."""
    import jax.numpy as jnp
    from tpu_device_plugin.validator.workload import (
        build_infer, forward, init_params)
    import jax
    mesh = slice_mesh(cpus()[:1])
    fwd, params, tokens = build_infer(SMALL, mesh, seed=11)
    logits = fwd(params, tokens)
    ref_params = init_params(jax.random.key(11), SMALL)
    ref = forward(ref_params, tokens, SMALL, "einsum", True, mesh)
    # bf16 matmuls: jit fusion order vs eager differs in the last few ulps,
    # which is ~3e-2 at these logit magnitudes
    assert float(jnp.max(jnp.abs(logits - ref))) < 1e-1


def test_attn_bench_cpu_small():
    """attn-bench sweep runs end-to-end in interpret mode on CPU."""
    from tpu_device_plugin.validator.attn_bench import bench_attention
    result = bench_attention(seq_lens=(64,), blocks=((32, 32), (64, 64)),
                             hb=2, head_dim=32, iters=2)
    assert result["platform"] == "cpu" and result["interpret"] is True
    assert len(result["cells"]) == 2
    for cell in result["cells"]:
        assert cell["error"] == ""
        assert cell["flash_fwd_ms"] > 0 and cell["einsum_train_ms"] > 0


def test_attn_bench_cli_json_line(capsys):
    from tpu_device_plugin.validator.probe import main
    rc = main(["--mode", "attn-bench", "--seqs", "64", "--blocks", "32x32",
               "--steps", "2"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    import json as json_mod
    payload = json_mod.loads(out)
    assert rc == 0 and payload["ok"] is True
    assert payload["cells"][0]["seq"] == 64


def test_attn_bench_partial_failure_keeps_cells(monkeypatch):
    """An einsum OOM at one seq must not discard other seqs' cells, and
    errored timings must serialize as JSON null, never NaN."""
    import json as json_mod
    from tpu_device_plugin.validator import attn_bench

    real_paired = attn_bench._paired_time

    def flaky(build, args, iters, repeats):
        if args[0].shape[1] == 128:  # the big seq "OOMs"
            raise MemoryError("RESOURCE_EXHAUSTED")
        return real_paired(build, args, iters, repeats)

    monkeypatch.setattr(attn_bench, "_paired_time", flaky)
    result = attn_bench.bench_attention(
        seq_lens=(64, 128), blocks=((32, 32),), hb=2, head_dim=32, iters=1)
    assert len(result["cells"]) == 2
    good, bad = result["cells"]
    assert good["error"] == "" and good["flash_fwd_ms"] > 0
    assert "MemoryError" in bad["error"]
    text = json_mod.dumps(result)
    assert "NaN" not in text
    assert json_mod.loads(text)["cells"][1]["flash_fwd_ms"] is None


def test_moe_training_step_single_device():
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=4, n_experts=4)
    step, params, momentum, tokens = build_workload(cfg, slice_mesh(cpus()[:1]))
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(5):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_pp_ep_sharded_training_step():
    """pipeline (stage-sharded stacked layers) x expert x tensor mesh."""
    mesh = slice_mesh(cpus(), pp=2, ep=2, tp=2, sp=1)
    assert mesh.axis_names == ("pp", "dp", "sp", "ep", "tp")
    assert mesh.devices.shape == (2, 1, 1, 2, 2)
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=4, n_experts=4)
    step, params, momentum, tokens = build_workload(cfg, mesh)
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(3):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_pp_ep_matches_single_device():
    """pp/ep sharding must not change the math (modulo bf16 noise)."""
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=4, n_experts=2)
    single, p1, m1, t1 = build_workload(cfg, slice_mesh(cpus()[:1]), seed=7)
    _, _, loss_single = single(p1, m1, t1)
    sharded, p8, m8, t8 = build_workload(
        cfg, slice_mesh(cpus(), pp=2, ep=2, tp=2, sp=1), seed=7)
    _, _, loss_sharded = sharded(p8, m8, t8)
    assert abs(float(loss_single) - float(loss_sharded)) < 2e-2


def test_moe_capacity_drops_do_not_break_training():
    """Tiny capacity factor forces token drops; training must still work."""
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                      seq_len=16, batch=4, n_experts=2, capacity_factor=0.25)
    step, params, momentum, tokens = build_workload(cfg, slice_mesh(cpus()[:1]))
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(5):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_cli_rejects_invalid_pp_ep_before_devices():
    """Bad --pp/--ep must be a usage error, never a broken-slice report."""
    from tpu_device_plugin.validator.probe import main
    with pytest.raises(SystemExit) as e:
        main(["--pp", "3"])  # does not divide n_layers=2
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        main(["--ep", "2"])  # dense model, nothing to shard
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        main(["--ep", "4", "--experts", "2"])
    assert e.value.code == 2


def test_gpipe_loss_matches_plain_forward():
    """Explicit GPipe schedule (pp2 x dp2, 4 microbatches) must compute the
    same loss as the unpipelined forward on the same params/tokens."""
    from tpu_device_plugin.validator.pipeline import build_gpipe, gpipe_loss_fn
    from tpu_device_plugin.validator.workload import init_params, loss_fn
    import jax.numpy as jnp
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=8)
    mesh = slice_mesh(cpus()[:4], pp=2, tp=1, sp=1)  # pp2 x dp2
    params = init_params(jax.random.key(5), cfg)
    tokens = jax.random.randint(jax.random.key(6), (cfg.batch, cfg.seq_len),
                                0, cfg.vocab, dtype=jnp.int32)
    piped = gpipe_loss_fn(params, tokens, cfg, mesh, n_micro=4)
    plain = loss_fn(params, tokens, cfg)
    assert abs(float(piped) - float(plain)) < 2e-2


def test_gpipe_gradients_match_plain():
    """The transposed schedule (backward sweep through the ppermutes) must
    produce the same gradients as differentiating the plain forward."""
    from tpu_device_plugin.validator.pipeline import gpipe_loss_fn
    from tpu_device_plugin.validator.workload import init_params, loss_fn
    import jax.numpy as jnp
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=8)
    mesh = slice_mesh(cpus()[:4], pp=2, tp=1, sp=1)
    params = init_params(jax.random.key(5), cfg)
    tokens = jax.random.randint(jax.random.key(6), (cfg.batch, cfg.seq_len),
                                0, cfg.vocab, dtype=jnp.int32)
    g_pipe = jax.grad(lambda p: gpipe_loss_fn(p, tokens, cfg, mesh, 4))(params)
    g_ref = jax.grad(lambda p: loss_fn(p, tokens, cfg))(params)
    flat_p, _ = jax.tree.flatten(g_pipe)
    flat_r, _ = jax.tree.flatten(g_ref)
    for a, b in zip(flat_p, flat_r):
        assert float(jnp.max(jnp.abs(a - b))) < 2e-2


def test_gpipe_training_decreases_loss():
    from tpu_device_plugin.validator.pipeline import build_gpipe
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=8)
    mesh = slice_mesh(cpus(), pp=2, tp=1, sp=1)  # pp2 x dp4
    step, params, momentum, tokens = build_gpipe(cfg, mesh, n_micro=2)
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(5):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_remat_matches_plain_gradients():
    """jax.checkpoint on the layer body must not change loss or grads."""
    import jax.numpy as jnp
    from dataclasses import replace as dc_replace
    from tpu_device_plugin.validator.workload import init_params, loss_fn
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=4)
    cfg_r = dc_replace(cfg, remat=True)
    params = init_params(jax.random.key(9), cfg)
    tokens = jax.random.randint(jax.random.key(10), (cfg.batch, cfg.seq_len),
                                0, cfg.vocab, dtype=jnp.int32)
    l_plain, g_plain = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg))(params)
    l_remat, g_remat = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg_r))(params)
    assert abs(float(l_plain) - float(l_remat)) < 1e-5
    for a, b in zip(*(jax.tree.flatten(g)[0] for g in (g_plain, g_remat))):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_remat_trains_on_mesh():
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=32, batch=4, remat=True)
    mesh = slice_mesh(cpus(), tp=2, sp=2)
    step, params, momentum, tokens = build_workload(cfg, mesh)
    params, momentum, loss0 = step(params, momentum, tokens)
    for _ in range(3):
        params, momentum, loss = step(params, momentum, tokens)
    assert float(loss) < float(loss0)


def test_gpipe_remat_matches():
    from tpu_device_plugin.validator.pipeline import gpipe_loss_fn
    from tpu_device_plugin.validator.workload import init_params
    import jax.numpy as jnp
    from dataclasses import replace as dc_replace
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=8)
    mesh = slice_mesh(cpus()[:4], pp=2, tp=1, sp=1)
    params = init_params(jax.random.key(5), cfg)
    tokens = jax.random.randint(jax.random.key(6), (cfg.batch, cfg.seq_len),
                                0, cfg.vocab, dtype=jnp.int32)
    plain = gpipe_loss_fn(params, tokens, cfg, mesh, 4)
    remat = gpipe_loss_fn(params, tokens, dc_replace(cfg, remat=True),
                          mesh, 4)
    assert abs(float(plain) - float(remat)) < 1e-5


def test_validate_slice_gpipe_mode():
    report = validate_slice(cfg=ModelConfig(
        vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
        seq_len=16, batch=8), steps=3, pp=2, tp=1, sp=1,
        devices=cpus()[:4], gpipe_microbatches=2)
    assert report.ok, report.error
    assert report.loss_end < report.loss_start
    assert report.mesh_shape["pp"] == 2


def test_cli_gpipe_requires_pp():
    from tpu_device_plugin.validator.probe import main
    with pytest.raises(SystemExit) as e:
        main(["--gpipe-microbatches", "2"])
    assert e.value.code == 2


def test_cli_gpipe_rejects_incompatible_flags():
    from tpu_device_plugin.validator.probe import main
    for argv in (["--gpipe-microbatches", "2", "--pp", "2", "--tp", "2"],
                 ["--gpipe-microbatches", "2", "--pp", "2",
                  "--attention", "flash"],
                 ["--gpipe-microbatches", "3", "--pp", "2"],  # 8 % 3 != 0
                 ["--mode", "infer", "--pp", "2",
                  "--gpipe-microbatches", "2"],
                 ["--mode", "attn-bench", "--gpipe-microbatches", "2"],
                 # ep would replicate the whole pipeline per expert rank
                 ["--gpipe-microbatches", "2", "--pp", "2",
                  "--ep", "2", "--experts", "4"]):
        with pytest.raises(SystemExit) as e:
            main(argv)
        assert e.value.code == 2, argv


def test_gpipe_loss_fn_rejects_ep_axis():
    """gpipe_loss_fn must reject an ep mesh axis like it rejects sp/tp —
    the schedule has no expert dispatch, so ep ranks would silently run
    identical replicated pipelines."""
    from tpu_device_plugin.validator.pipeline import gpipe_loss_fn
    from tpu_device_plugin.validator.workload import init_params
    import jax.numpy as jnp
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=8)
    mesh = slice_mesh(cpus()[:4], pp=2, ep=2)
    params = init_params(jax.random.key(5), cfg)
    tokens = jnp.zeros((cfg.batch, cfg.seq_len), jnp.int32)
    with pytest.raises(ValueError, match="ep"):
        gpipe_loss_fn(params, tokens, cfg, mesh, n_micro=4)


def test_slice_mesh_pp_ep_divisibility_errors():
    with pytest.raises(ValueError, match="not divisible by pp"):
        slice_mesh(cpus()[:6], pp=4)
    with pytest.raises(ValueError, match="not divisible by pp"):
        slice_mesh(cpus()[:6], ep=4)
    # pp/ep axes appear only when > 1
    assert slice_mesh(cpus()[:8], pp=1, ep=1).axis_names == ("dp", "sp", "tp")
    assert slice_mesh(cpus()[:8], ep=2).axis_names == ("dp", "sp", "ep", "tp")


def test_gpipe_local_batch_mismatch_is_config_error():
    """Non-dividing LOCAL batch (only knowable once dp is known) must be a
    config verdict with exit code 2, never a broken-slice report."""
    cfg = ModelConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                      seq_len=16, batch=8)
    report = validate_slice(cfg=cfg, steps=2, pp=2, tp=1, sp=1,
                            devices=cpus(), gpipe_microbatches=4)
    # pp2 x dp4 -> local batch 2, not divisible by 4
    assert report.invalid_config and not report.ok
    assert "invalid configuration" in report.error
