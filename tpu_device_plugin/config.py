"""Injectable configuration, replacing the reference's package-global knobs.

The reference keeps every tunable as a compile-time package var that doubles
as a test seam (reference: pkg/device_plugin/device_plugin.go:70-87). Here a
single `Config` dataclass is threaded through discovery, servers, and health;
tests construct one pointed at tmpdir fixtures instead of monkeypatching
globals.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Optional

from .kubeletapi.api import (
    DEVICE_PLUGIN_PATH as _DEVICE_PLUGIN_PATH,
    KUBELET_SOCKET as _KUBELET_SOCKET,
)


@dataclass(frozen=True)
class Config:
    # --- sysfs / devfs roots (tests point these at tmpdir fixtures) ---------
    pci_base_path: str = "/sys/bus/pci/devices"
    mdev_base_path: str = "/sys/bus/mdev/devices"
    accel_class_path: str = "/sys/class/accel"
    # Root prefixed onto absolute /dev and /sys paths that are probed at
    # Allocate/health time (reference: device_plugin.go:76 `rootPath`).
    root_path: str = "/"
    pci_ids_path: str = "/usr/pci.ids"

    # --- kubelet contract (defaults from kubeletapi contract constants) -----
    device_plugin_path: str = _DEVICE_PLUGIN_PATH
    kubelet_socket: str = _KUBELET_SOCKET
    socket_prefix: str = "tpukubevirt"
    # DRA (dra.py): the kubelet watches dra_registry_path for registration
    # sockets; the driver's service socket lives under dra_plugins_path.
    dra_plugins_path: str = "/var/lib/kubelet/plugins/"
    dra_registry_path: str = "/var/lib/kubelet/plugins_registry/"
    # Persisted discovery snapshot (discovery.HostSnapshot.save_cache):
    # lives beside the DRA checkpoint so both restart artifacts share one
    # durability story. None disables persistence entirely.
    discovery_snapshot_path: Optional[str] = \
        "/var/lib/kubelet/plugins/discovery-snapshot.json"

    # --- resource naming ----------------------------------------------------
    # Extended-resource namespace: devices surface as
    # `cloud-tpus.google.com/<generation>` (reference advertises
    # `nvidia.com/<pci.ids name>`, generic_device_plugin.go:57).
    resource_namespace: str = "cloud-tpus.google.com"
    # KubeVirt externalResourceProvider env prefix: KubeVirt's virt-launcher
    # selects passed-through PCI devices from
    # `PCI_RESOURCE_<RESOURCE_NAME>` (reference: generic_device_plugin.go:58).
    env_prefix: str = "PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM"
    vtpu_env_prefix: str = "MDEV_PCI_RESOURCE_CLOUD_TPUS_GOOGLE_COM"

    # --- discovery filters --------------------------------------------------
    vendor_ids: tuple[str, ...] = ("1ae0",)  # Google, Inc.
    # Accepted out of the box: the generic driver plus a vendor-variant name,
    # mirroring the reference's built-in second driver (nvgrace_gpu_vfio_pci,
    # device_plugin.go:75-78). No TPU vfio variant driver is public today;
    # accepting the plausible name is harmless (the vendor-id filter still
    # gates discovery) and saves operator action if one ships. More via
    # --vfio-drivers.
    vfio_drivers: tuple[str, ...] = ("vfio-pci", "tpu_vfio_pci")
    # Optional JSON file overriding the built-in device-id → generation table
    # (tpu_device_plugin/data/tpu_ids.json ships the defaults; fleets override).
    generation_map_path: Optional[str] = None
    # Optional JSON file mapping BDF → ICI torus coordinates for hosts whose
    # physical chip order differs from BDF order.
    topology_hints_path: Optional[str] = None
    # This host's slot on the POD-LEVEL host grid (e.g. (0, 3) on a 4x8
    # v5e pod), published as hostX/hostY[/hostZ] ResourceSlice attributes
    # so the fleet placement control plane (fleetplace.py) can model the
    # pod's wrap-around inter-host ICI links. None = unknown (the fleet
    # scheduler then treats cross-host contiguity for this host as
    # unmodeled). Set via --host-coords "x,y[,z]" / $TDP_HOST_COORDS.
    host_coords: Optional[tuple[int, ...]] = None

    # --- vTPU partitions ----------------------------------------------------
    # Optional JSON file declaring logical partitions of physical chips for
    # hosts without mdev support (see vtpu.py).
    partition_config_path: Optional[str] = None
    # Hard cap on advertised logical partitions per parent chip (0 = only
    # the generation's cores_per_chip / the explicit list applies). Logical
    # partitions share one /dev/accelN with NO hardware isolation
    # (docs/design.md "vTPU trust boundary") — the cap bounds the blast
    # radius of one chip's tenants.
    max_partitions_per_chip: int = 0
    # Device-node permissions handed to VMIs for accel-backed logical
    # partitions: "rw" (default) or "r" where the guest stack tolerates a
    # read-only node. mdev/vfio-backed partitions keep "mrw" — VFIO needs
    # mmap, and isolation there is kernel-mediated anyway.
    partition_node_permissions: str = "rw"

    # --- shared host devices (EGM analogue, reference #9) -------------------
    # sysfs class dirs scanned for shared devices spanning multiple chips;
    # each entry must contain a membership file listing chip BDFs.
    shared_device_classes: tuple[str, ...] = ("/sys/class/egm",)

    # --- timing -------------------------------------------------------------
    grpc_timeout_s: float = 5.0      # registration dial bound (reference :53)
    health_poll_s: float = 5.0       # native liveness probe cadence (NVML parity)
    # Shared health plane (healthhub.HealthHub): bounded worker pool for the
    # deduped per-BDF liveness probes, and the wall-clock deadline one probe
    # cycle may spend collecting verdicts — a hung config-space read is
    # scored dead at the deadline instead of serializing every other chip's
    # verdict behind it.
    health_probe_workers: int = 4
    health_probe_deadline_s: float = 1.0
    # Attach plane (dra.py): bounded worker pool fanning a multi-claim
    # NodePrepareResources/NodeUnprepareResources out so concurrent claims
    # never queue behind each other's API-server fetch or sysfs reads.
    # Same-UID retries still serialize on a per-claim lock (idempotency).
    prepare_workers: int = 4
    rediscovery_interval_s: float = 0.0  # 0 disables periodic re-discovery
    # ListAndWatch coalesce window: health transitions landing within this
    # window are folded into ONE re-send (a vfio flap storm otherwise
    # re-streams the whole device list N times). Trailing-edge: a lone flip
    # still propagates after one quiet window; 0 restores send-per-flip.
    # Validated at plugin arm time (server.py rejects negative/NaN loudly).
    lw_debounce_s: float = 0.05
    # Dirty-set rediscovery (discovery.HostSnapshot): the periodic timer
    # rescans only changed/flapped devices instead of walking all of sysfs.
    # False (--full-rescan) restores the full walk on every tick.
    incremental_rediscovery: bool = True
    # Shared-device (EGM-analogue) scan cache TTL inside a plugin server's
    # Allocate path. 0 = rescan every Allocate (the reference's behavior,
    # generic_device_plugin.go:366); a small TTL keeps hotplug visible within
    # seconds while taking the sysfs walk off the per-RPC critical path.
    shared_scan_ttl_s: float = 1.0
    # ResourceSlice publish pacing (kubeapi.PublishPacer): the admission
    # window starts at base and ADAPTS — 429/slow-RTT feedback doubles it
    # (bounded by max), fast successes decay it back. base 0 means an
    # uncongested node publishes with zero added latency; the window only
    # opens when the apiserver pushes back (fleet boot storms).
    publish_pace_base_s: float = 0.0
    publish_pace_max_s: float = 2.0
    # /status diagnostics cache TTL: the per-device latched-PCI-error +
    # link-training reads cost 2 sysfs reads per device per scrape — at
    # 4096 devices that is 8192 reads per /status. A small TTL serves
    # repeat scrapes from the last read set. 0 = always live (default;
    # single-rack inventories are cheap to read fresh).
    diagnostics_ttl_s: float = 0.0

    # --- privilege separation (broker.py) -----------------------------------
    # "inproc" (default): privileged operations run in this process
    # through the audited in-process seam. "spawn": cli.main starts the
    # privileged broker as a separate process and every privileged
    # operation crosses the versioned IPC — the serving daemon can then
    # run unprivileged and crash/upgrade freely while the broker keeps
    # its device fds. Env override: $TDP_BROKER.
    broker_mode: str = "inproc"
    # unix socket the broker serves its IPC on (the serving daemon
    # reconnects here after either side restarts)
    broker_socket_path: str = "/var/run/tpu-device-plugin/broker.sock"

    # --- operator policy hooks (policy.py) ----------------------------------
    # Directory of sandboxed policy modules (*.py) hooking allocation
    # scoring, health verdicts, and admission; None disables the engine.
    policy_dir: Optional[str] = None
    # wall-clock budget per hook call: a result arriving later is
    # discarded (builtin behavior), counted, and charged to the hook's
    # circuit breaker
    policy_hook_deadline_ms: float = 25.0

    # --- native shim --------------------------------------------------------
    native_lib_path: Optional[str] = None  # override libtpuhealth.so location

    # --- CDI ----------------------------------------------------------------
    # When set, write CDI specs here (e.g. /var/run/cdi) and return CDIDevice
    # names from Allocate alongside the classic DeviceSpecs.
    cdi_spec_dir: Optional[str] = None

    def dev_path(self, *parts: str) -> str:
        """Join an absolute devfs/sysfs path under root_path."""
        return os.path.join(self.root_path, *[p.lstrip("/") for p in parts])

    def with_root(self, root: str) -> "Config":
        """Convenience for tests: re-root every filesystem path under `root`."""
        return replace(
            self,
            pci_base_path=os.path.join(root, "sys/bus/pci/devices"),
            mdev_base_path=os.path.join(root, "sys/bus/mdev/devices"),
            accel_class_path=os.path.join(root, "sys/class/accel"),
            root_path=root,
            device_plugin_path=os.path.join(root, "device-plugins/"),
            kubelet_socket=os.path.join(root, "device-plugins/kubelet.sock"),
            dra_plugins_path=os.path.join(root, "plugins/"),
            dra_registry_path=os.path.join(root, "plugins_registry/"),
            discovery_snapshot_path=os.path.join(
                root, "plugins/discovery-snapshot.json"),
            shared_device_classes=(os.path.join(root, "sys/class/egm"),),
            broker_socket_path=os.path.join(root, "run/broker.sock"),
        )
